#!/usr/bin/env python
"""On-chip MFU hunt: the measurement companion to the flash-kernel and
GPT-MFU tuning work (round-4 verdict item 4: flagship MFU >= 0.40).

Three probe families, each printing one JSON line (prefix `HUNT:`) so the
tpu_retry queue can run this unattended and the results land in a log:

  peak    true MXU rate per (m, k, n) via a dependent matmul chain —
          every iteration's output feeds the next input, so XLA can
          neither hoist the matmul nor slice through an unused output
          (both happened with naive timing loops; see RESULTS.md r4).
  flash   our Pallas flash fwd+grad at the flagship GPT attention shape,
          swept over (block_q, block_k), head layout (16x64 vs 8x128),
          and backward arm, vs jax.experimental's reference TPU kernel.

Usage:  python scripts/mfu_hunt.py [peak|flash|all]  (default all)
Unknown probe names exit nonzero so an unattended queue retries/surfaces
the typo instead of recording a silent no-op success.
"""
from __future__ import annotations

import functools
import json
import sys
import time

import numpy as np


def _sync(x) -> float:
    """Force execution through the axon tunnel (block_until_ready can
    return early there): fetch one element of the LAST result."""
    import jax

    leaf = jax.tree.leaves(x)[0]
    return float(np.asarray(leaf.reshape(-1)[0], np.float32))


def probe_peak() -> None:
    import jax
    import jax.numpy as jnp
    from jax import lax

    k1, k2 = jax.random.split(jax.random.PRNGKey(0))

    def bench(m, k_, n, iters, dtype=jnp.bfloat16):
        x = jax.random.normal(k1, (m, k_), dtype) * 0.01
        w = jax.random.normal(k2, (k_, n), dtype) * 0.01

        @jax.jit
        def run(x, w):
            def body(x, _):
                y = x @ w  # [m, n]
                # fold a NONLINEAR reduction of the WHOLE output back into
                # the next input: abs blocks the algebraic rewrite
                # sum(dot(x, w)) -> dot(x, sum(w)) (and any slice-through),
                # so every output element is live and the matmul cannot be
                # hoisted or shrunk.  Costs one VPU pass over y (~10% on
                # the widest shape) — accepted, and in the safe direction
                # (reported peak is a slight UNDERestimate).
                feedback = jnp.sum(jnp.abs(y), axis=1, keepdims=True)
                return (x + feedback * 1e-6).astype(dtype) * 0.5, ()

            x, _ = lax.scan(body, x, None, length=iters)
            return x

        _sync(run(x, w))  # compile + warm
        t0 = time.perf_counter()
        _sync(run(x, w))
        dt = (time.perf_counter() - t0) / iters
        return {
            "shape": [m, k_, n],
            "ms": round(dt * 1e3, 4),
            "tflops": round(2 * m * k_ * n / dt / 1e12, 1),
        }

    rows = [
        bench(4096, 4096, 4096, 100),
        bench(8192, 1024, 32000, 40),   # lm head
        bench(8192, 1024, 4096, 100),   # mlp in
        bench(8192, 1024, 1024, 100),   # qkv/out proj
        bench(8192, 1024, 1024, 100, jnp.float32),  # f32 comparison point
    ]
    print("HUNT: " + json.dumps({"probe": "peak", "rows": rows}), flush=True)


def probe_flash() -> None:
    import jax
    import jax.numpy as jnp

    from kungfu_tpu.ops.flash import flash_attention

    B, L = 4, 2048
    rng = np.random.RandomState(0)

    def arms():
        for heads, dim in ((16, 64), (8, 128)):
            for bq, bk in ((128, 128), (256, 256), (512, 512), (256, 512),
                           (512, 1024)):
                yield ("ours", heads, dim, bq, bk)
        # the blocked-XLA backward (auto choice below seq 4096) reads
        # block_k as its scan granularity — sweep it too
        for heads, dim in ((16, 64), (8, 128)):
            for bq, bk in ((128, 128), (128, 512)):
                yield ("ours_xla_bwd", heads, dim, bq, bk)
        for heads, dim in ((16, 64), (8, 128)):
            yield ("jax_ref", heads, dim, 0, 0)

    def time_arm(kind, heads, dim, bq, bk):
        shape = (B, L, heads, dim)
        q, k, v = (jnp.asarray(rng.randn(*shape), jnp.bfloat16)
                   for _ in range(3))
        if kind in ("ours", "ours_xla_bwd"):
            fn = functools.partial(
                flash_attention, causal=True, block_q=bq, block_k=bk,
                backward="pallas" if kind == "ours" else "xla",
            )
        else:
            from jax.experimental.pallas.ops.tpu.flash_attention import (
                flash_attention as jax_flash,
            )

            def fn(q, k, v):
                # jax ref kernel wants [B, H, L, D]
                t = lambda x: x.transpose(0, 2, 1, 3)
                return t(jax_flash(t(q), t(k), t(v), causal=True))

        def loss(q, k, v):
            return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

        g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        _sync(g(q, k, v))
        steps = 10
        t0 = time.perf_counter()
        r = None
        for _ in range(steps):
            r = g(q, k, v)
        _sync(r)
        dt = (time.perf_counter() - t0) / steps
        return {
            "impl": kind, "heads": heads, "head_dim": dim,
            "block_q": bq, "block_k": bk, "ms": round(dt * 1e3, 3),
        }

    rows = []
    for arm in arms():
        try:
            rows.append(time_arm(*arm))
        except Exception as e:  # one bad tiling must not sink the sweep
            rows.append({"impl": arm[0], "heads": arm[1], "head_dim": arm[2],
                         "block_q": arm[3], "block_k": arm[4],
                         "error": f"{type(e).__name__}: {e}"[:200]})
        print("HUNT: " + json.dumps({"probe": "flash", "row": rows[-1]}),
              flush=True)
    best = min((r for r in rows if "ms" in r), key=lambda r: r["ms"],
               default=None)
    print("HUNT: " + json.dumps({"probe": "flash", "rows": rows,
                                 "best": best}), flush=True)


def main(argv) -> int:
    which = argv[1] if len(argv) > 1 else "all"
    if which not in ("peak", "flash", "all"):
        print(f"# mfu_hunt: unknown probe {which!r} "
              "(expected peak|flash|all)", file=sys.stderr)
        return 2
    import jax

    print(f"# mfu_hunt: backend={jax.default_backend()} "
          f"devices={jax.devices()}", flush=True)
    if jax.default_backend() != "tpu":
        print("HUNT: " + json.dumps({"error": "not on tpu"}), flush=True)
        return 1
    if which in ("peak", "all"):
        probe_peak()
    if which in ("flash", "all"):
        probe_flash()
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
